"""Serving layer: scheduler/microbatcher units, metrics, caches, and an
end-to-end HTTP service on the CPU backend with the tiny config.

The load-bearing guarantee: a request served through the whole stack
(HTTP -> scheduler -> continuous-batched engine -> step_many) is
BIT-identical to ``Sampler.synthesize`` with the same per-request rng —
the engine replays the offline loop's exact key-split stream, and on a
fixed backend the object-batched program matches the single-object one
bitwise (pinned here; cross-backend it would be float-tolerance only).
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from diff3d_tpu.config import ServingConfig
from diff3d_tpu.config import test_config as make_tiny_config
from diff3d_tpu.data import SyntheticDataset
from diff3d_tpu.models import XUNet
from diff3d_tpu.sampling import Sampler, record_capacity
from diff3d_tpu.serving import (Bucket, MetricsRegistry, ParamsRegistry,
                                QueueFullError, RequestTimeout, ResultCache,
                                Scheduler, ServingService, ViewRequest,
                                make_http_server)
from diff3d_tpu.train.trainer import init_params


def _views_dict(ds, i):
    v = ds.all_views(i)
    return {"imgs": np.asarray(v["imgs"]), "R": np.asarray(v["R"]),
            "T": np.asarray(v["T"]), "K": np.asarray(v["K"])}


def _mk_request(ds, i, n_views=3, seed=0, timeout_s=None):
    return ViewRequest(_views_dict(ds, i), seed=seed, n_views=n_views,
                       timeout_s=timeout_s)


@pytest.fixture(scope="module")
def tiny_ds():
    return SyntheticDataset(num_objects=4, num_views=6, imgsize=8)


# ---------------------------------------------------------------------------
# Scheduler / microbatcher units (no device work)
# ---------------------------------------------------------------------------


def test_request_validation_and_bucketing(tiny_ds):
    r3 = _mk_request(tiny_ds, 0, n_views=3)
    r5 = _mk_request(tiny_ds, 1, n_views=5)
    # capacity rounds to powers of two — 3 views -> 4, 5 views -> 8
    assert r3.bucket == Bucket(8, 8, 4)
    assert r5.bucket == Bucket(8, 8, 8)
    assert r3.bucket.capacity == record_capacity(3)
    with pytest.raises(ValueError):
        _mk_request(tiny_ds, 0, n_views=1)      # nothing to synthesise
    bad = _views_dict(tiny_ds, 0)
    bad["K"] = np.zeros((2, 2))
    with pytest.raises(ValueError):
        ViewRequest(bad)


def test_scheduler_groups_by_bucket(tiny_ds):
    s = Scheduler(max_queue=8, max_wait_s=0.0)
    a = s.submit(_mk_request(tiny_ds, 0, n_views=3))
    b = s.submit(_mk_request(tiny_ds, 1, n_views=5))
    c = s.submit(_mk_request(tiny_ds, 2, n_views=3))
    got = s.acquire(a.bucket, max_n=8, block=False)
    assert [r.id for r in got] == [a.id, c.id]   # same bucket, FIFO
    assert s.depth() == 1
    got2 = s.acquire(None, max_n=8, block=True, poll_s=0.5)
    assert [r.id for r in got2] == [b.id]
    assert s.depth() == 0


def test_scheduler_max_wait_flushes_underfull_batch(tiny_ds):
    s = Scheduler(max_queue=8, max_wait_s=0.15)
    s.submit(_mk_request(tiny_ds, 0))
    t0 = time.monotonic()
    got = s.acquire(None, max_n=4, block=True, poll_s=5.0)
    waited = time.monotonic() - t0
    assert len(got) == 1
    # held for the flush deadline (minus epsilon), not the full poll
    assert 0.1 <= waited < 3.0


def test_scheduler_full_batch_skips_the_wait(tiny_ds):
    s = Scheduler(max_queue=8, max_wait_s=5.0)
    for i in range(3):
        s.submit(_mk_request(tiny_ds, i))
    t0 = time.monotonic()
    got = s.acquire(None, max_n=3, block=True, poll_s=10.0)
    assert len(got) == 3
    assert time.monotonic() - t0 < 1.0           # no 5s flush wait


def test_scheduler_bounded_queue_rejects(tiny_ds):
    m = MetricsRegistry()
    s = Scheduler(max_queue=2, max_wait_s=0.0, metrics=m)
    s.submit(_mk_request(tiny_ds, 0))
    s.submit(_mk_request(tiny_ds, 1))
    with pytest.raises(QueueFullError):
        s.submit(_mk_request(tiny_ds, 2))
    assert m.snapshot()["counters"][
        "serving_requests_rejected_total"] == 1


def test_scheduler_request_timeout_swept(tiny_ds):
    m = MetricsRegistry()
    s = Scheduler(max_queue=8, max_wait_s=0.0, metrics=m)
    req = s.submit(_mk_request(tiny_ds, 0, timeout_s=0.01))
    time.sleep(0.05)
    assert s.acquire(req.bucket, max_n=4, block=False) == []
    assert req.done()
    with pytest.raises(RequestTimeout):
        req.result(timeout=0)
    assert m.snapshot()["counters"]["serving_requests_timeout_total"] == 1


def test_request_cancellation(tiny_ds):
    s = Scheduler(max_queue=8, max_wait_s=0.0)
    req = s.submit(_mk_request(tiny_ds, 0))
    assert req.cancel()
    assert s.acquire(req.bucket, max_n=4, block=False) == []
    assert req.done() and req.error is not None
    assert not req.cancel()                      # already resolved


# ---------------------------------------------------------------------------
# Metrics / caches units
# ---------------------------------------------------------------------------


def test_metrics_snapshot_and_exposition():
    m = MetricsRegistry()
    m.counter("c_total", "a counter").inc(3)
    m.gauge("g", "a gauge").set(7)
    h = m.histogram("h_seconds", "a histogram")
    for v in range(1, 101):
        h.observe(v / 100.0)
    snap = m.snapshot()
    assert snap["counters"]["c_total"] == 3
    assert snap["gauges"]["g"] == 7
    hs = snap["histograms"]["h_seconds"]
    assert hs["count"] == 100
    assert 0.45 <= hs["p50"] <= 0.55 and hs["p99"] >= 0.95
    text = m.exposition()
    assert "# TYPE c_total counter" in text
    assert 'h_seconds{quantile="p50"}' in text
    assert "h_seconds_count 100" in text
    json.dumps(snap)                             # JSON-able


def test_result_cache_lru_eviction():
    c = ResultCache(capacity=2)
    c.put("a", np.zeros(1)); c.put("b", np.ones(1))
    assert c.get("a") is not None                # refresh 'a'
    c.put("c", np.ones(1))                       # evicts 'b' (LRU)
    assert c.get("b") is None
    assert c.get("a") is not None and c.get("c") is not None
    assert len(c) == 2


def test_result_cache_key_sensitivity(tiny_ds):
    r = _mk_request(tiny_ds, 0, seed=1)
    assert r.content_key("v0") == r.content_key("v0")
    assert r.content_key("v0") != r.content_key("v1")   # params version
    r2 = _mk_request(tiny_ds, 0, seed=2)
    assert r.content_key("v0") != r2.content_key("v0")  # rng seed


def test_params_registry_guards_shape(setup_service):
    _, _, params, *_ = setup_service
    reg = ParamsRegistry(params, version="v0")
    v = reg.swap(params)                         # same tree ok
    assert v == "v1" and reg.version == "v1"
    bad = jax.tree.map(lambda x: np.zeros(x.shape + (1,), x.dtype), params)
    with pytest.raises(ValueError):
        reg.swap(bad)


# ---------------------------------------------------------------------------
# End-to-end: HTTP service on the CPU backend, tiny config
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def setup_service(tiny_ds):
    cfg = make_tiny_config(imgsize=8, ch=8)
    cfg = dataclasses.replace(
        cfg, serving=ServingConfig(port=0, max_batch=4, max_queue=8,
                                   max_wait_ms=400.0, max_views=6,
                                   default_timeout_s=120.0))
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    sampler = Sampler(model, params, cfg)
    service = ServingService(sampler, cfg).start(serve_http=True)
    yield cfg, model, params, sampler, service, tiny_ds
    service.stop()


def _post(port, payload, timeout=300):
    data = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/synthesize", data=data,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(port, path, timeout=30):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as r:
        return r.status, r.read()


def _payload(ds, i, n_views=3, seed=0, **kw):
    v = _views_dict(ds, i)
    return {"views": {k: a.tolist() for k, a in v.items()},
            "seed": seed, "n_views": n_views, **kw}


def test_http_concurrent_requests_bit_identical_and_batched(setup_service):
    """The acceptance pin: N concurrent HTTP requests come back
    bit-identical to the direct Sampler path, are co-batched (occupancy
    > 1), and /healthz + /metrics answer while the job is in flight."""
    cfg, model, params, sampler, service, ds = setup_service
    port = service.port
    results, errs = {}, []

    def worker(i):
        try:
            status, body = _post(port, _payload(ds, i, seed=100 + i))
            assert status == 200
            results[i] = body
        except Exception as e:                   # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    # Liveness while the engine is busy: both endpoints answer now.
    status, body = _get(port, "/healthz")
    assert status == 200 and json.loads(body)["engine_alive"]
    status, body = _get(port, "/metrics")
    assert status == 200 and b"serving_queue_depth" in body
    for t in threads:
        t.join()
    assert not errs

    for i in range(3):
        direct = sampler.synthesize(ds.all_views(i),
                                    jax.random.PRNGKey(100 + i),
                                    max_views=3)
        got = np.asarray(results[i]["views"], np.float32)
        assert results[i]["shape"] == list(direct.shape)
        np.testing.assert_array_equal(got, direct)

    snap = service.metrics_snapshot()
    occ = snap["histograms"]["serving_batch_occupancy"]
    assert occ["max"] > 1, f"requests were never co-batched: {occ}"
    assert snap["counters"]["serving_views_completed_total"] >= 6
    assert snap["histograms"]["serving_time_to_first_view_seconds"][
        "count"] >= 3


def test_http_continuous_batching_admits_mid_job(setup_service):
    """A short job submitted while a long job is mid-flight must join at
    the next view boundary (iteration-level scheduling), not wait for the
    long job to finish."""
    cfg, model, params, sampler, service, ds = setup_service
    port = service.port
    long_res = {}

    def long_worker():
        _, long_res["body"] = _post(port, _payload(ds, 0, n_views=5,
                                                   seed=7))

    t = threading.Thread(target=long_worker)
    before = service.metrics_snapshot()["counters"][
        "serving_views_completed_total"]
    t.start()
    # Wait until the long job has completed >= 1 view, then submit.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        done = service.metrics_snapshot()["counters"][
            "serving_views_completed_total"]
        if done > before:
            break
        time.sleep(0.02)
    # Same bucket (n_views=5 -> capacity 8): admitted at the next view
    # boundary, several views behind the long job.
    status, short = _post(port, _payload(ds, 1, n_views=5, seed=8))
    assert status == 200
    t.join()
    long_direct = sampler.synthesize(ds.all_views(0),
                                     jax.random.PRNGKey(7), max_views=5)
    short_direct = sampler.synthesize(ds.all_views(1),
                                      jax.random.PRNGKey(8), max_views=5)
    np.testing.assert_array_equal(
        np.asarray(long_res["body"]["views"], np.float32), long_direct)
    np.testing.assert_array_equal(
        np.asarray(short["views"], np.float32), short_direct)
    occ = service.metrics_snapshot()["histograms"][
        "serving_batch_occupancy"]
    assert occ["max"] > 1


def test_http_result_cache_replay(setup_service):
    cfg, model, params, sampler, service, ds = setup_service
    port = service.port
    p = _payload(ds, 2, seed=42)
    s1, r1 = _post(port, p)
    s2, r2 = _post(port, p)
    assert s1 == s2 == 200
    assert not r1["cached"] and r2["cached"]
    np.testing.assert_array_equal(np.asarray(r1["views"]),
                                  np.asarray(r2["views"]))
    assert service.metrics_snapshot()["counters"][
        "serving_result_cache_hits_total"] >= 1


def test_http_request_timeout_is_explicit(setup_service):
    cfg, model, params, sampler, service, ds = setup_service
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(service.port, _payload(ds, 3, seed=9, timeout_s=0.0))
    assert ei.value.code == 504
    body = json.loads(ei.value.read())
    assert "deadline" in body["error"]


def test_http_validation_errors(setup_service):
    cfg, model, params, sampler, service, ds = setup_service
    port = service.port
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, {"seed": 1})                 # no views
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, _payload(ds, 0, n_views=60))  # over max_views
    assert ei.value.code == 400
    status, _ = _get(port, "/metrics?format=json")
    assert status == 200
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/result/nope")
    assert ei.value.code == 404


def test_http_poll_path(setup_service):
    cfg, model, params, sampler, service, ds = setup_service
    port = service.port
    status, body = _post(port, _payload(ds, 1, seed=11, block=False))
    assert status == 202 and body["status"] == "pending"
    rid = body["id"]
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        status, raw = _get(port, f"/result/{rid}")
        if status == 200:
            break
        assert status == 202
        time.sleep(0.05)
    out = json.loads(raw)
    direct = sampler.synthesize(ds.all_views(1), jax.random.PRNGKey(11),
                                max_views=3)
    np.testing.assert_array_equal(np.asarray(out["views"], np.float32),
                                  direct)


@pytest.mark.compile_budget(0)
def test_hot_params_swap_changes_output_without_recompile(setup_service,
                                                          compile_sentinel):
    cfg, model, params, sampler, service, ds = setup_service
    port = service.port
    p = _payload(ds, 3, seed=13)
    _, base = _post(port, p)
    # Zero-compile budget from here on: the first request above compiled
    # the view-step program; a params swap must re-enter it (params is a
    # jit *argument*, never baked into the executable).
    compile_sentinel.track("view_step", sampler._run_view_many)

    # A different random init is NOT enough here: the X-UNet's output
    # conv is zero-initialised, so any fresh init predicts eps=0 and the
    # sample is params-independent.  Perturb every leaf instead.
    params2 = jax.tree.map(lambda x: x + np.asarray(0.05, x.dtype), params)
    service.registry.swap(params2, version="ckpt-2")
    try:
        assert json.loads(_get(port, "/healthz")[1])[
            "params_version"] == "ckpt-2"
        _, swapped = _post(port, p)
        # different weights -> different views; and a different cache key,
        # so this was NOT a result-cache replay
        assert not swapped["cached"]
        assert not np.array_equal(np.asarray(base["views"]),
                                  np.asarray(swapped["views"]))
    finally:
        service.registry.swap(params, version="v0")
    # The compile_budget(0) marker fails the test at teardown if the
    # swap minted a new executable.


def test_queue_full_and_degraded_health_over_http(setup_service):
    """Backpressure at the HTTP boundary: with the engine down and a
    1-deep queue, the second submission gets 429 and /healthz reports
    degraded — requests are never silently queued without bound."""
    cfg, model, params, sampler, service, ds = setup_service
    cfg2 = dataclasses.replace(
        cfg, serving=dataclasses.replace(cfg.serving, max_queue=1))
    stalled = ServingService(sampler, cfg2)      # engine NOT started
    httpd = make_http_server(stalled, "127.0.0.1", 0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    port = httpd.server_address[1]
    try:
        status, _ = _post(port, _payload(ds, 0, block=False))
        assert status == 202
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, _payload(ds, 1, block=False))
        assert ei.value.code == 429
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(port, "/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "degraded"
    finally:
        httpd.shutdown()
        httpd.server_close()
        stalled.scheduler.close()


@pytest.mark.slow
def test_serving_soak_waves(setup_service):
    """Soak: several waves of mixed-size jobs; everything completes,
    bit-identical, and the queue drains to zero."""
    cfg, model, params, sampler, service, ds = setup_service
    port = service.port
    jobs = [(i % 4, 2 + (i % 3), 200 + i) for i in range(12)]
    results = {}

    def worker(j, obj, n, seed):
        _, results[j] = _post(port, _payload(ds, obj, n_views=n,
                                             seed=seed))

    threads = [threading.Thread(target=worker, args=(j, *job))
               for j, job in enumerate(jobs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for j, (obj, n, seed) in enumerate(jobs):
        direct = sampler.synthesize(ds.all_views(obj),
                                    jax.random.PRNGKey(seed), max_views=n)
        np.testing.assert_array_equal(
            np.asarray(results[j]["views"], np.float32), direct)
    assert service.scheduler.depth() == 0
