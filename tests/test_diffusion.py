import jax
import jax.numpy as jnp
import numpy as np

from diff3d_tpu.diffusion import (alpha_sigma, logsnr_schedule_cosine,
                                  make_model_batch, p_losses,
                                  p_mean_variance, q_sample, sample_loop)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_schedule_endpoints():
    # t=0 -> logsnr_max, t=1 -> logsnr_min (closed form of
    # -2 log(tan(a t + b))).
    np.testing.assert_allclose(float(logsnr_schedule_cosine(jnp.array(0.0))),
                               20.0, atol=5e-3)
    np.testing.assert_allclose(float(logsnr_schedule_cosine(jnp.array(1.0))),
                               -20.0, atol=5e-3)


def test_schedule_monotone_and_midpoint():
    t = jnp.linspace(0.0, 1.0, 101)
    ls = np.asarray(logsnr_schedule_cosine(t))
    assert (np.diff(ls) < 0).all()
    # closed-form midpoint
    b = np.arctan(np.exp(-10.0))
    a = np.arctan(np.exp(10.0)) - b
    np.testing.assert_allclose(ls[50], -2 * np.log(np.tan(a * 0.5 + b)),
                               rtol=1e-5, atol=1e-5)


def test_alpha_sigma_variance_preserving():
    logsnr = jnp.linspace(-20, 20, 11)
    a, s = alpha_sigma(logsnr)
    np.testing.assert_allclose(np.asarray(a ** 2 + s ** 2), 1.0, rtol=1e-6)


def test_q_sample_closed_form():
    B, H, W = 3, 4, 4
    z = jnp.ones((B, H, W, 3)) * 0.5
    noise = jnp.ones((B, H, W, 3)) * 2.0
    logsnr = jnp.array([-5.0, 0.0, 5.0])
    out = np.asarray(q_sample(z, logsnr, noise))
    for i, l in enumerate([-5.0, 0.0, 5.0]):
        expect = (np.sqrt(_sigmoid(l)) * 0.5 + np.sqrt(_sigmoid(-l)) * 2.0)
        np.testing.assert_allclose(out[i], expect, rtol=1e-5)


def test_make_model_batch_cond_logsnr_is_max():
    B = 4
    x = jnp.zeros((B, 8, 8, 3))
    batch = make_model_batch(x, x, jnp.full((B,), -3.0),
                             jnp.zeros((B, 2, 3, 3)), jnp.zeros((B, 2, 3)),
                             jnp.zeros((B, 3, 3)))
    assert batch["logsnr"].shape == (B, 2)
    # conditioning frame is clean: logsnr = schedule max = 20
    np.testing.assert_allclose(np.asarray(batch["logsnr"][:, 0]), 20.0)
    np.testing.assert_allclose(np.asarray(batch["logsnr"][:, 1]), -3.0)


def test_p_mean_variance_closed_form():
    B, H, W = 2, 4, 4
    rng = np.random.RandomState(0)
    z = rng.randn(B, H, W, 3).astype(np.float32)
    ec = rng.randn(B, H, W, 3).astype(np.float32)
    eu = rng.randn(B, H, W, 3).astype(np.float32)
    logsnr, logsnr_next = 1.5, 2.5
    w = np.array([0.0, 3.0], np.float32)

    mean, var = p_mean_variance(jnp.asarray(ec), jnp.asarray(eu),
                                jnp.asarray(z), jnp.array(logsnr),
                                jnp.array(logsnr_next), jnp.asarray(w))

    # independent numpy reproduction of the ancestral step
    c = -np.expm1(logsnr - logsnr_next)
    alpha = np.sqrt(_sigmoid(logsnr))
    sigma = np.sqrt(_sigmoid(-logsnr))
    alpha_next = np.sqrt(_sigmoid(logsnr_next))
    eps = (1 + w[:, None, None, None]) * ec - w[:, None, None, None] * eu
    z0 = np.clip((z - sigma * eps) / alpha, -1, 1)
    expect_mean = alpha_next * (z * (1 - c) / alpha + c * z0)
    expect_var = _sigmoid(-logsnr_next) * c

    np.testing.assert_allclose(np.asarray(mean), expect_mean, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(float(var), expect_var, rtol=1e-5)


def test_p_losses_zero_when_perfect():
    # a denoiser that returns the true noise gives (near-)zero loss; we use
    # the identity that loss is mse(noise, eps_hat).
    B, H, W = 4, 8, 8
    imgs = jnp.zeros((B, 2, H, W, 3))
    R = jnp.broadcast_to(jnp.eye(3), (B, 2, 3, 3))
    T = jnp.zeros((B, 2, 3))
    K = jnp.broadcast_to(jnp.eye(3), (B, 3, 3))

    captured = {}

    def perfect_denoiser(batch, cond_mask):
        # recover the noise from z_noisy = alpha*0 + sigma*eps
        logsnr = batch["logsnr"][:, 1]
        _, sigma = alpha_sigma(logsnr)
        captured["cond_mask"] = cond_mask
        return batch["z"] / sigma[:, None, None, None]

    loss = p_losses(perfect_denoiser, imgs, R, T, K,
                    jax.random.PRNGKey(0), cond_prob=0.5)
    assert float(loss) < 1e-6
    assert captured["cond_mask"].shape == (B,)


def test_p_losses_types():
    B, H, W = 2, 4, 4
    imgs = jnp.zeros((B, 2, H, W, 3))
    R = jnp.broadcast_to(jnp.eye(3), (B, 2, 3, 3))
    T = jnp.zeros((B, 2, 3))
    K = jnp.broadcast_to(jnp.eye(3), (B, 3, 3))

    def zero_denoiser(batch, cond_mask):
        return jnp.zeros_like(batch["z"])

    for lt in ("l1", "l2", "huber"):
        loss = p_losses(zero_denoiser, imgs, R, T, K, jax.random.PRNGKey(1),
                        loss_type=lt)
        assert np.isfinite(float(loss)) and float(loss) > 0


def test_sample_loop_shapes_and_finiteness():
    B, H, W, N = 3, 8, 8, 5

    def fake_denoiser(batch, cond_mask):
        # 2B folded batch comes in; return zeros (model predicts no noise)
        return jnp.zeros_like(batch["z"])

    out = sample_loop(
        fake_denoiser,
        record_imgs=jnp.zeros((N, B, H, W, 3)),
        record_R=jnp.broadcast_to(jnp.eye(3), (N, 3, 3)),
        record_T=jnp.zeros((N, 3)),
        record_len=jnp.array(2),
        target_R=jnp.eye(3),
        target_T=jnp.ones(3),
        K=jnp.eye(3),
        w=jnp.arange(B, dtype=jnp.float32),
        rng=jax.random.PRNGKey(0),
        timesteps=4)
    assert out.shape == (B, H, W, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_sample_loop_jits():
    B, H, W, N = 2, 8, 8, 3

    def fake_denoiser(batch, cond_mask):
        return jnp.zeros_like(batch["z"])

    f = jax.jit(lambda rng: sample_loop(
        fake_denoiser,
        record_imgs=jnp.zeros((N, B, H, W, 3)),
        record_R=jnp.broadcast_to(jnp.eye(3), (N, 3, 3)),
        record_T=jnp.zeros((N, 3)),
        record_len=jnp.array(1),
        target_R=jnp.eye(3), target_T=jnp.ones(3), K=jnp.eye(3),
        w=jnp.arange(B, dtype=jnp.float32), rng=rng, timesteps=3))
    out = f(jax.random.PRNGKey(1))
    assert out.shape == (B, H, W, 3)
