import json
import os

import numpy as np
import pytest

from diff3d_tpu.cli import sample_cli, train_cli


def test_train_cli_flag_parity():
    """Reference flags (--transfer/--train_data/--val_data) must parse."""
    p = train_cli.build_parser()
    args = p.parse_args(["--transfer", "--train_data", "/x",
                         "--val_data", "/y"])
    assert args.transfer and args.train_data == "/x"


def test_train_cli_parallelism_and_model_flags():
    p = train_cli.build_parser()
    args = p.parse_args(["--context_parallel", "--model_parallel", "2",
                         "--remat", "--remat_policy", "dots",
                         "--attn_impl", "xla"])
    assert args.context_parallel and args.model_parallel == 2
    assert args.remat is True and args.remat_policy == "dots"
    assert args.attn_impl == "xla"
    assert p.parse_args(["--no-remat"]).remat is False
    assert p.parse_args([]).remat is None
    # ring/ulysses need a caller-bound shard_map axis; the trainer can't
    # provide one, so the CLI rejects them (use --context_parallel there).
    with pytest.raises(SystemExit):
        p.parse_args(["--attn_impl", "ring:model"])


def test_sample_cli_flag_parity():
    p = sample_cli.build_parser()
    args = p.parse_args(["--model", "/ckpt", "--target", "/obj"])
    assert args.model == "/ckpt" and args.target == "/obj"


@pytest.mark.slow
def test_train_then_sample_cli_end_to_end(tmp_path):
    """Smoke the full user path: train 2 steps on synthetic data, then
    sample from the checkpoint (test config, tiny shapes)."""
    wd = str(tmp_path)
    train_cli.main(["--synthetic", "--config", "test", "--steps", "2",
                    "--batch", "8", "--workdir", wd, "--num_workers", "0"])
    assert os.path.exists(os.path.join(wd, "metrics.jsonl"))
    with open(os.path.join(wd, "metrics.jsonl")) as f:
        recs = [json.loads(l) for l in f]
    assert recs[-1]["step"] == 2 and np.isfinite(recs[-1]["loss"])
    ckpt_root = os.path.join(wd, "checkpoints")
    assert os.path.isdir(os.path.join(ckpt_root, "2"))

    # fake one SRN object dir for the sampler
    from PIL import Image
    obj = tmp_path / "objects" / "car0"
    for sub in ("rgb", "pose", "intrinsics"):
        (obj / sub).mkdir(parents=True)
    rng = np.random.default_rng(0)
    for v in range(3):
        name = f"{v:06d}"
        Image.fromarray(
            rng.integers(0, 255, (16, 16, 3), dtype=np.uint8).astype(
                np.uint8)).save(obj / "rgb" / f"{name}.png")
        pose = np.eye(4)
        pose[:3, 3] = [2.0, 0.1 * v, 0.3]
        np.savetxt(obj / "pose" / f"{name}.txt", pose.reshape(1, 16))
        K = np.array([[19.0, 0, 8], [0, 19.0, 8], [0, 0, 1]])
        np.savetxt(obj / "intrinsics" / f"{name}.txt", K.reshape(1, 9))

    out = str(tmp_path / "sampling")
    sample_cli.main(["--model", ckpt_root, "--target", str(obj),
                     "--config", "test", "--out", out, "--max_views", "2",
                     "--steps", "4"])
    assert os.path.exists(os.path.join(out, "1", "gt.png"))
    assert os.path.exists(os.path.join(out, "1", "0.png"))


@pytest.mark.slow
def test_eval_cli_resume_and_w_select(tmp_path):
    """Outage-proofing + validation-selected guidance: each object's
    synthesis lands on disk as it completes; a re-run skips completed
    objects and produces the IDENTICAL final record; --w_select picks w
    on objects disjoint from the eval set."""
    from diff3d_tpu.cli import eval_cli

    wd = str(tmp_path)
    train_cli.main(["--synthetic", "--config", "test", "--steps", "2",
                    "--batch", "8", "--workdir", wd, "--num_workers", "0"])
    ckpt_root = os.path.join(wd, "checkpoints")

    out = str(tmp_path / "eval.jsonl")
    argv = ["--model", ckpt_root, "--synthetic_scenes", "--config", "test",
            "--objects", "2", "--w_select", "1", "--steps", "2",
            "--max_views", "3", "--out", out]
    eval_cli.main(argv)
    rec1 = json.loads(open(out).read().strip().splitlines()[-1])
    assert rec1["objects"] == 2
    assert 0 <= rec1["w_selected"] < len(rec1["psnr_per_w"])
    # selection object is drawn AFTER the eval set — disjoint by design
    assert rec1["w_select_objects"] == ["2"]
    assert "psnr_margin_mean_w_selected" in rec1

    objdir = out + ".objdir"
    npzs = sorted(f for f in os.listdir(objdir) if f.endswith(".npz"))
    # record names carry the checkpoint step (here 2): a later-step eval
    # re-synthesises instead of tripping over stale records
    assert npzs == ["obj_s2_0.npz", "obj_s2_1.npz", "obj_s2_2.npz"]
    kept = os.path.getmtime(os.path.join(objdir, "obj_s2_0.npz"))
    os.remove(os.path.join(objdir, "obj_s2_1.npz"))  # simulate lost obj

    eval_cli.main(argv)  # resumes: only obj_1 is re-synthesised
    rec2 = json.loads(open(out).read().strip().splitlines()[-1])
    assert rec2 == rec1
    assert os.path.getmtime(os.path.join(objdir, "obj_s2_0.npz")) == kept

    # a record made under different settings must be refused, not mixed in
    argv_other_steps = list(argv)
    argv_other_steps[argv.index("--steps") + 1] = "4"
    with pytest.raises(SystemExit, match="different settings"):
        eval_cli.main(argv_other_steps)

    # longitudinal workflow: train further, re-run the SAME eval command
    # — the new checkpoint step keys fresh records (stale ones ignored,
    # not a fatal protocol conflict)
    train_cli.main(["--synthetic", "--config", "test", "--steps", "4",
                    "--batch", "8", "--workdir", wd, "--num_workers", "0",
                    "--transfer"])
    eval_cli.main(argv)
    rec3 = json.loads(open(out).read().strip().splitlines()[-1])
    assert rec3["checkpoint_step"] == 4
    npzs = sorted(f for f in os.listdir(objdir) if f.endswith(".npz"))
    assert [f for f in npzs if f.startswith("obj_s4_")] == [
        "obj_s4_0.npz", "obj_s4_1.npz", "obj_s4_2.npz"]


@pytest.mark.slow
def test_eval_cli_end_to_end(tmp_path, capsys):
    """Train 2 steps, then score PSNR/SSIM/FID on a fake val object."""
    from diff3d_tpu.cli import eval_cli

    wd = str(tmp_path)
    train_cli.main(["--synthetic", "--config", "test", "--steps", "2",
                    "--batch", "8", "--workdir", wd, "--num_workers", "0"])
    ckpt_root = os.path.join(wd, "checkpoints")

    # fake SRN split dir with two objects x 3 views (val split non-empty
    # needs train_fraction < 1; the default 0.9 keeps >= 1 of 10 in val)
    from PIL import Image
    rng = np.random.default_rng(1)
    data_dir = tmp_path / "srn"
    for o in range(10):
        obj = data_dir / f"obj{o}"
        for sub in ("rgb", "pose", "intrinsics"):
            (obj / sub).mkdir(parents=True)
        for v in range(3):
            name = f"{v:06d}"
            Image.fromarray(rng.integers(0, 255, (16, 16, 3),
                                         dtype=np.uint8)).save(
                obj / "rgb" / f"{name}.png")
            pose = np.eye(4)
            pose[:3, 3] = [2.0, 0.1 * v, 0.3]
            np.savetxt(obj / "pose" / f"{name}.txt", pose.reshape(1, 16))
            K = np.array([[19.0, 0, 8], [0, 19.0, 8], [0, 0, 1]])
            np.savetxt(obj / "intrinsics" / f"{name}.txt", K.reshape(1, 9))

    out_jsonl = str(tmp_path / "eval.jsonl")
    eval_cli.main(["--model", ckpt_root, "--val_data", str(data_dir),
                   "--config", "test", "--objects", "1", "--steps", "2",
                   "--max_views", "3", "--out", out_jsonl])
    rec = json.loads(open(out_jsonl).read().strip())
    assert rec["views"] >= 2 and np.isfinite(rec["psnr"])
    assert np.isfinite(rec["fid_randfeat"]) and -1 <= rec["ssim"] <= 1
